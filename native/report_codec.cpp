// Native TLS-syntax scanner for the DAP aggregation-init hot path.
//
// The reference keeps its whole runtime native (Rust); here the service
// plane is Python with this C++ core under the per-report wire parsing:
// given the body of an AggregationJobInitializeReq, emit a table of field
// offsets/lengths for every PrepareInit so Python slices buffers instead of
// walking bytes per field.  Layout parsed (messages/src/lib.rs:2114,2185):
//
//   PrepareInit = ReportShare || opaque32 message
//   ReportShare = report_id[16] || time u64 || opaque32 public_share
//                 || HpkeCiphertext(config_id u8 || opaque16 enc_key
//                                   || opaque32 payload)
//
// Output row (10 x int64 per report):
//   [id_off, time, pub_off, pub_len, config_id, enc_off, enc_len,
//    ct_off, ct_len, msg_off]  plus msg_len in the 11th column.
//
// Returns the number of reports parsed, or -1 on malformed input.

#include <cstdint>
#include <cstddef>

extern "C" {

static inline uint16_t rd16(const uint8_t* p) {
    return (uint16_t(p[0]) << 8) | p[1];
}
static inline uint32_t rd32(const uint8_t* p) {
    return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16)
         | (uint32_t(p[2]) << 8) | p[3];
}
static inline uint64_t rd64(const uint8_t* p) {
    uint64_t v = 0;
    for (int i = 0; i < 8; ++i) v = (v << 8) | p[i];
    return v;
}

long parse_prepare_inits(const uint8_t* buf, long len, long max_reports,
                         int64_t* out /* max_reports x 11 */) {
    long off = 0;
    long n = 0;
    while (off < len) {
        if (n >= max_reports) return -1;
        int64_t* row = out + n * 11;
        // ReportMetadata
        if (off + 16 + 8 > len) return -1;
        row[0] = off;                    // report id offset
        row[1] = (int64_t)rd64(buf + off + 16);  // time (seconds)
        off += 24;
        // public share
        if (off + 4 > len) return -1;
        uint32_t pub_len = rd32(buf + off);
        off += 4;
        if (off + pub_len > (uint64_t)len) return -1;
        row[2] = off;
        row[3] = pub_len;
        off += pub_len;
        // HpkeCiphertext
        if (off + 1 + 2 > len) return -1;
        row[4] = buf[off];
        off += 1;
        uint16_t enc_len = rd16(buf + off);
        off += 2;
        if (off + enc_len + 4 > len) return -1;
        row[5] = off;
        row[6] = enc_len;
        off += enc_len;
        uint32_t ct_len = rd32(buf + off);
        off += 4;
        if (off + ct_len + 4 > (uint64_t)len) return -1;
        row[7] = off;
        row[8] = ct_len;
        off += ct_len;
        // ping-pong message
        uint32_t msg_len = rd32(buf + off);
        off += 4;
        if (off + msg_len > (uint64_t)len) return -1;
        row[9] = off;
        row[10] = msg_len;
        off += msg_len;
        ++n;
    }
    return off == len ? n : -1;
}

// Batched XOR-of-SHA256 checksum support: XOR `n` 32-byte digests into out.
void xor_digests(const uint8_t* digests, long n, uint8_t* out /* 32 */) {
    for (int i = 0; i < 32; ++i) out[i] = 0;
    for (long k = 0; k < n; ++k)
        for (int i = 0; i < 32; ++i) out[i] ^= digests[k * 32 + i];
}

}  // extern "C"
